"""Accuracy sweep: sketch outputs vs the exact oracle across traffic shapes.

Covers BASELINE.json configs 2-4:

- config 2 — Count-Min + top-K heavy hitters (recall@100 and F1 vs the exact
  per-key byte aggregation), swept over zipf skew x CM width x K x window
  mode (reset vs decay);
- config 3 — HLL distinct-source cardinality, single-device and merged over
  a 4-way data mesh;
- config 4 — RTT/DNS log-histogram quantiles vs exact numpy quantiles.

Run `python scripts/accuracy_sweep.py` to (re)generate docs/accuracy.md.
tests/test_accuracy_sweep.py runs a reduced grid with hard guards at the
BASELINE bound (<1% heavy-hitter recall loss).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from netobserv_tpu.utils.platform import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from netobserv_tpu.sketch import state as sk  # noqa: E402

BATCH = 4096
N_BATCHES = 24
N_DISTINCT = 20_000
RECALL_AT = 100


def make_traffic(zipf_s: float, seed: int, n_batches: int = N_BATCHES):
    """Zipf-skewed batches + the exact per-key byte totals."""
    rng = np.random.default_rng(seed)
    universe = rng.integers(0, 2**32, (N_DISTINCT, 10), dtype=np.uint32)
    batches = []
    exact = np.zeros(N_DISTINCT, np.float64)
    rtt_all = []
    for _ in range(n_batches):
        ranks = np.minimum(rng.zipf(zipf_s, BATCH) - 1, N_DISTINCT - 1)
        byts = rng.integers(64, 9000, BATCH).astype(np.float32)
        rtt = rng.lognormal(9.0, 1.2, BATCH).astype(np.int32)  # ~µs scale
        np.add.at(exact, ranks, byts.astype(np.float64))
        rtt_all.append(rtt)
        batches.append({
            "keys": universe[ranks],
            "bytes": byts,
            "packets": np.ones(BATCH, np.int32),
            "rtt_us": rtt,
            "dns_latency_us": np.maximum(rtt // 7, 1).astype(np.int32),
            "sampling": np.zeros(BATCH, np.int32),
            "valid": np.ones(BATCH, np.bool_),
        })
    distinct_true = int((exact > 0).sum())
    return universe, batches, exact, distinct_true, np.concatenate(rtt_all)


def heavy_metrics(report_heavy, universe, exact, k_eval=RECALL_AT):
    true_top = np.argsort(-exact)[:k_eval]
    got = {tuple(w) for w, v in zip(np.asarray(report_heavy.words),
                                    np.asarray(report_heavy.valid)) if v}
    hits = sum(tuple(universe[t]) in got for t in true_top)
    recall = hits / k_eval
    # F1 of the reported set vs the true top-|reported| set
    n_rep = max(len(got), 1)
    true_set = {tuple(universe[t]) for t in np.argsort(-exact)[:n_rep]}
    tp = len(got & true_set)
    prec = tp / n_rep
    rec = tp / max(len(true_set), 1)
    f1 = 2 * prec * rec / max(prec + rec, 1e-9)
    return recall, f1


def run_case(zipf_s: float, width: int, k: int, mode: str, seed: int = 0,
             tiered: bool = False):
    universe, batches, exact, distinct_true, rtt_all = make_traffic(
        zipf_s, seed)
    tiers = None
    if tiered:
        # tiered counter planes (SKETCH_TIERED) at the production tier
        # geometry — graded against the SAME bars as the wide path
        from netobserv_tpu.sketch.tiered import TierSpec
        tiers = TierSpec()
    cfg = sk.SketchConfig(cm_width=width, topk=k, tiered=tiers)
    state = sk.init_state(cfg)
    ingest = jax.jit(sk.ingest)
    if mode == "reset":
        for arrays in batches:
            state = ingest(state, {k2: jnp.asarray(v)
                                   for k2, v in arrays.items()})
        state, report = sk.roll_window(state, cfg)
    else:  # decay: roll (decay 0.8) every 8 batches; oracle decays likewise
        for i, arrays in enumerate(batches):
            if i and i % 8 == 0:
                state = sk.decay_state(state, 0.8)
            state = ingest(state, {k2: jnp.asarray(v)
                                   for k2, v in arrays.items()})
        # exact decayed-mass oracle from the same stream (same seed)
        rng = np.random.default_rng(seed)
        universe2 = rng.integers(0, 2**32, (N_DISTINCT, 10), dtype=np.uint32)
        assert (universe2 == universe).all()
        decayed = np.zeros(N_DISTINCT, np.float64)
        seg_seen = np.zeros(N_DISTINCT, np.bool_)
        for i in range(N_BATCHES):
            ranks = np.minimum(rng.zipf(zipf_s, BATCH) - 1, N_DISTINCT - 1)
            byts = rng.integers(64, 9000, BATCH).astype(np.float32)
            rng.lognormal(9.0, 1.2, BATCH)
            if i and i % 8 == 0:
                decayed *= 0.8
                seg_seen[:] = False  # HLL registers reset at decay
            np.add.at(decayed, ranks, byts.astype(np.float64))
            seg_seen[ranks] = True
        exact = decayed
        distinct_true = int(seg_seen.sum())  # distinct since last reset
        state, report = sk.roll_window(state, cfg)
    recall, f1 = heavy_metrics(report.heavy, universe, exact)
    hll_err = abs(float(report.distinct_src) - distinct_true) / distinct_true
    # config 4: quantiles vs exact (reset-mode rtt stream only)
    q_err = None
    if mode == "reset":
        qs = np.asarray(report.rtt_quantiles_us)
        truth = np.quantile(rtt_all, sk.QS)
        q_err = float(np.max(np.abs(qs - truth) / truth))
    return recall, f1, hll_err, q_err


def _keys_for_pairs(rng, src_words, dst_words, n):
    """(n, 10) u32 key arrays from given 4-word src/dst blocks + random
    ports (word 8) and proto TCP (word 9)."""
    kw = np.zeros((n, 10), np.uint32)
    kw[:, 0:4] = src_words
    kw[:, 4:8] = dst_words
    kw[:, 8] = (rng.integers(1024, 65535, n).astype(np.uint32) << 16) | 443
    kw[:, 9] = np.uint32(6 << 16)
    return kw


def _signal_arrays(kw, flags, drop_bytes=None, drop_packets=None,
                   drop_cause=None):
    n = len(kw)
    zeros = np.zeros(n, np.int32)
    return {
        "keys": kw, "bytes": np.full(n, 100.0, np.float32),
        "packets": np.ones(n, np.int32), "rtt_us": zeros,
        "dns_latency_us": zeros, "sampling": zeros,
        "valid": np.ones(n, np.bool_),
        "tcp_flags": np.asarray(flags, np.int32), "dscp": zeros,
        "drop_bytes": (zeros if drop_bytes is None
                       else np.asarray(drop_bytes, np.int32)),
        "drop_packets": (zeros if drop_packets is None
                         else np.asarray(drop_packets, np.int32)),
        "drop_cause": (zeros if drop_cause is None
                       else np.asarray(drop_cause, np.int32)),
    }


def _victim_bucket(dst_words, m):
    from netobserv_tpu.ops import hashing
    h1, _ = hashing.base_hashes(
        jnp.asarray(dst_words[None, :], jnp.uint32), seed=hashing.DST_BUCKET_SEED)
    return int(np.asarray(h1)[0] & (m - 1))


def run_synflood_case(flood_n: int, bg_flows: int = 8192, seed: int = 0,
                      synflood_min: float = 128.0, ratio: float = 8.0):
    """SYN-flood signal sweep: a half-open flood of `flood_n` records at one
    victim over a healthy handshake background. Returns (detected,
    false_positives, victim_syn, victim_synack)."""
    rng = np.random.default_rng(seed)
    cfg = sk.SketchConfig(cm_width=1 << 12, topk=64)
    m = cfg.ewma_buckets
    state = sk.init_state(cfg)
    ingest = jax.jit(sk.ingest)
    services = rng.integers(0, 2**32, (64, 4), dtype=np.uint32)
    victim = rng.integers(0, 2**32, 4, dtype=np.uint32)
    # healthy background: every client SYN (client flow flags SYN|ACK) is
    # answered by a server SYN-ACK response flow in the victim-bucket sense
    svc = services[rng.integers(0, 64, bg_flows)]
    clients = rng.integers(0, 2**32, (bg_flows, 4), dtype=np.uint32)
    state = ingest(state, _signal_arrays(
        _keys_for_pairs(rng, clients, svc, bg_flows),
        np.full(bg_flows, 0x12)))
    state = ingest(state, _signal_arrays(
        _keys_for_pairs(rng, svc, clients, bg_flows),
        np.full(bg_flows, 0x112)))
    # the flood: spoofed sources, SYN never completed, no responses
    spoofed = rng.integers(0, 2**32, (flood_n, 4), dtype=np.uint32)
    state = ingest(state, _signal_arrays(
        _keys_for_pairs(rng, spoofed, np.tile(victim, (flood_n, 1)),
                        flood_n),
        np.full(flood_n, 0x02)))
    _, report = sk.roll_window(state, cfg)
    syn = np.asarray(report.syn_rate)
    synack = np.asarray(report.synack_rate)
    flagged = set(np.nonzero((syn >= synflood_min)
                             & (syn >= ratio * (synack + 1.0)))[0].tolist())
    vb = _victim_bucket(victim, m)
    detected = vb in flagged
    return detected, len(flagged - {vb}), float(syn[vb]), float(synack[vb])


def run_drop_case(storm_factor: float, seed: int = 0, z_threshold: float = 6.0,
                  calm_windows: int = 6):
    """Drop-anomaly sweep: `calm_windows` windows of background drop noise
    seed the EWMA baseline, then a storm of `storm_factor` x the noise level
    at one victim. Returns (detected, false_positives, victim_z,
    max_other_z). Short baselines (< ~5 windows) produce a few z>6 noise
    buckets — the variance estimate needs that many samples to settle."""
    rng = np.random.default_rng(seed)
    cfg = sk.SketchConfig(cm_width=1 << 12, topk=64)
    m = cfg.ewma_buckets
    state = sk.init_state(cfg)
    ingest = jax.jit(sk.ingest)
    dsts = rng.integers(0, 2**32, (256, 4), dtype=np.uint32)
    victim = dsts[7]
    n = 4096

    def window(storm: bool):
        dst = dsts[rng.integers(0, 256, n)]
        src = rng.integers(0, 2**32, (n, 4), dtype=np.uint32)
        noise = rng.integers(0, 40, n)
        db = noise.copy()
        if storm:
            hit = np.zeros(n, np.bool_)
            hit[: n // 8] = True
            dst[hit] = victim
            db[hit] = int(40 * storm_factor)
        return _signal_arrays(_keys_for_pairs(rng, src, dst, n),
                              np.full(n, 0x12), drop_bytes=db,
                              drop_packets=(db > 0).astype(np.int32),
                              drop_cause=np.full(n, 2))

    report = None
    for i in range(calm_windows + 1):
        state = ingest(state, window(storm=(i == calm_windows)))
        state, report = sk.roll_window(state, cfg)
    z = np.asarray(report.drop_z)
    flagged = set(np.nonzero(z > z_threshold)[0].tolist())
    vb = _victim_bucket(victim, m)
    others = np.delete(z, vb)
    return (vb in flagged, len(flagged - {vb}), float(z[vb]),
            float(others.max()))


def run_asym_case(elephant_mb: float, bg_pairs: int = 512, seed: int = 0,
                  min_bytes: float = 1 << 20, ratio: float = 0.95):
    """Conversation-asymmetry sweep: one-way elephants of `elephant_mb`
    against balanced background conversations (each direction ~512KB).
    Returns (detected, false_positives)."""
    rng = np.random.default_rng(seed)
    cfg = sk.SketchConfig(cm_width=1 << 12, topk=64)
    state = sk.init_state(cfg)
    ingest = jax.jit(sk.ingest)
    a_ends = rng.integers(0, 2**32, (bg_pairs, 4), dtype=np.uint32)
    b_ends = rng.integers(0, 2**32, (bg_pairs, 4), dtype=np.uint32)
    per_dir = 512 * 1024 / 8  # 8 records each way per pair
    for src, dst in ((a_ends, b_ends), (b_ends, a_ends)):
        for _ in range(8):
            kw = _keys_for_pairs(rng, src, dst, bg_pairs)
            arrays = _signal_arrays(kw, np.full(bg_pairs, 0x12))
            arrays["bytes"] = np.full(bg_pairs, per_dir, np.float32)
            state = ingest(state, arrays)
    exfil_src = rng.integers(0, 2**32, 4, dtype=np.uint32)
    exfil_dst = rng.integers(0, 2**32, 4, dtype=np.uint32)
    kw = _keys_for_pairs(rng, np.tile(exfil_src, (8, 1)),
                         np.tile(exfil_dst, (8, 1)), 8)
    arrays = _signal_arrays(kw, np.full(8, 0x12))
    arrays["bytes"] = np.full(8, elephant_mb * (1 << 20) / 8, np.float32)
    state = ingest(state, arrays)
    _, report = sk.roll_window(state, cfg)
    fwd = np.asarray(report.conv_fwd)
    rev = np.asarray(report.conv_rev)
    total = fwd + rev
    share = np.maximum(fwd, rev) / np.maximum(total, 1.0)
    flagged = set(np.nonzero((total >= min_bytes) & (share >= ratio))[0]
                  .tolist())
    from netobserv_tpu.ops import hashing
    s_h, _ = hashing.base_hashes(
        jnp.asarray(exfil_src[None, :], jnp.uint32), seed=hashing.DST_BUCKET_SEED)
    d_h, _ = hashing.base_hashes(
        jnp.asarray(exfil_dst[None, :], jnp.uint32), seed=hashing.DST_BUCKET_SEED)
    vb = int((np.asarray(s_h)[0] + np.asarray(d_h)[0])
             & (cfg.ewma_buckets - 1))
    return vb in flagged, len(flagged - {vb})


def run_mesh_hll_case(zipf_s: float, seed: int = 0):
    """Config 3: distinct-src over a 4-way data mesh, merged over the mesh."""
    from netobserv_tpu.parallel import MeshSpec, make_mesh, merge as pmerge

    ndata = 4
    if ndata > len(jax.devices()):
        return None
    universe, batches, exact, distinct_true, _ = make_traffic(zipf_s, seed)
    cfg = sk.SketchConfig(cm_width=1 << 14, topk=256)
    mesh = make_mesh(MeshSpec(data=ndata, sketch=1))
    dist = pmerge.init_dist_state(cfg, mesh)
    ingest_fn = pmerge.make_sharded_ingest_fn(mesh, cfg, donate=False)
    merge_fn = pmerge.make_merge_fn(mesh, cfg)
    for arrays in batches:
        n = (len(arrays["valid"]) // ndata) * ndata
        dist = ingest_fn(dist, pmerge.shard_batch(
            mesh, {k: v[:n] for k, v in arrays.items()}))
    _, report = merge_fn(dist)
    return abs(float(report.distinct_src) - distinct_true) / distinct_true


def main() -> None:
    rows = []
    for zipf_s in (1.1, 1.2, 1.5, 2.0):
        for width in (1 << 12, 1 << 14, 1 << 16):
            for k in (256, 1024):
                for mode in ("reset", "decay"):
                    r, f1, he, qe = run_case(zipf_s, width, k, mode)
                    rows.append((zipf_s, width, k, mode, r, f1, he, qe))
                    print(f"s={zipf_s} w={width} K={k} {mode}: "
                          f"recall={r:.3f} f1={f1:.3f} hll={he:.4f} "
                          f"q={qe if qe is None else round(qe, 4)}",
                          file=sys.stderr)
    mesh_rows = []
    for zipf_s in (1.2, 1.5):
        e = run_mesh_hll_case(zipf_s)
        if e is not None:
            mesh_rows.append((zipf_s, e))
    syn_rows = []
    for flood_n in (128, 512, 2048):
        det, fp, syn, synack = run_synflood_case(flood_n)
        syn_rows.append((flood_n, det, fp, syn, synack))
        print(f"synflood n={flood_n}: detected={det} fp={fp}",
              file=sys.stderr)
    drop_rows = []
    for factor in (5.0, 10.0, 100.0):
        det, fp, vz, oz = run_drop_case(factor)
        drop_rows.append((factor, det, fp, vz, oz))
        print(f"drop x{factor}: detected={det} fp={fp} z={vz:.1f}",
              file=sys.stderr)
    asym_rows = []
    for mb in (1.5, 4.0, 16.0, 256.0):
        runs = [run_asym_case(mb, seed=s) for s in range(8)]
        det = sum(d for d, _ in runs) / len(runs)
        fp = sum(f for _, f in runs)
        asym_rows.append((mb, det, fp))
        print(f"asym {mb}MB: detection rate={det:.2f} fp={fp}",
              file=sys.stderr)

    out = os.path.join(os.path.dirname(__file__), "..", "docs", "accuracy.md")
    with open(out, "w") as fh:
        fh.write(
            "# Accuracy sweep — sketches vs the exact oracle\n\n"
            "Generated by `python scripts/accuracy_sweep.py` "
            f"({N_BATCHES} batches x {BATCH} zipf records, {N_DISTINCT} "
            "distinct keys; guards enforced by tests/test_accuracy_sweep.py)."
            "\n\nBASELINE bound: <1% heavy-hitter recall loss vs exact "
            "aggregation (BASELINE.json configs 2-4).\n\n"
            "## Config 2: heavy hitters (recall@100 / F1) + config 4 "
            "(max quantile rel. err)\n\n"
            "| zipf s | CM width | K | window | recall@100 | F1 | "
            "HLL err | RTT quantile err |\n|---|---|---|---|---|---|---|---|\n")
        for zipf_s, width, k, mode, r, f1, he, qe in rows:
            fh.write(f"| {zipf_s} | {width} | {k} | {mode} | {r:.3f} | "
                     f"{f1:.3f} | {he:.4f} | "
                     f"{'—' if qe is None else f'{qe:.4f}'} |\n")
        fh.write("\n## Config 3: distinct-src HLL, merged over a 4-way "
                 "data mesh\n\n| zipf s | HLL rel. err |\n|---|---|\n")
        for zipf_s, e in mesh_rows:
            fh.write(f"| {zipf_s} | {e:.4f} |\n")
        fh.write(
            "\n## Config 5 signals: SYN-flood detection "
            "(8192 healthy handshakes background; gates min=128, ratio=8)\n\n"
            "| flood half-opens | detected | false-positive buckets | "
            "victim SYN | victim SYN-ACK |\n|---|---|---|---|---|\n")
        for flood_n, det, fp, syn, synack in syn_rows:
            fh.write(f"| {flood_n} | {det} | {fp} | {syn:.0f} | "
                     f"{synack:.0f} |\n")
        fh.write(
            "\n## Config 5 signals: drop-anomaly z-score "
            "(6 calm baseline windows, storm at one victim, z > 6)\n\n"
            "| storm vs noise | detected | false-positive buckets | "
            "victim z | max other z |\n|---|---|---|---|---|\n")
        for factor, det, fp, vz, oz in drop_rows:
            fh.write(f"| {factor:.0f}x | {det} | {fp} | {vz:.0f} | "
                     f"{oz:.1f} |\n")
        fh.write(
            "\n## Config 5 signals: conversation asymmetry "
            "(512 balanced 1MB background pairs; gates 1MB floor, "
            "0.95 one-way share; 8 seeds per row)\n\n"
            "| one-way elephant | detection rate | false-positive buckets "
            "(all runs) |\n|---|---|---|\n")
        for mb, det, fp in asym_rows:
            fh.write(f"| {mb}MB | {det:.2f} | {fp} |\n")
        fh.write(
            "\nAsymmetry note: elephants near the volume floor can be "
            "muted by a pair-bucket collision with balanced background "
            "traffic (12.5% odds at 512 pairs / 4096 buckets) — the share "
            "dilutes below the gate. Sizing the floor a few x below the "
            "flows you care about restores headroom; false positives stay "
            "at zero throughout.\n")
        fh.write(
            "\nNotes: recall is vs the true top-100 keys by byte volume; "
            "F1 compares the full reported table against the equal-size "
            "true set, so small-width tables score lower on near-uniform "
            "(s=1.1) traffic where the 'heavy' set is ill-defined. The "
            "decay-mode oracle applies the same geometric decay to the "
            "exact counts. HLL error at the default precision (2^14 "
            "registers) has sigma ~0.8%.\n")
    print(f"wrote {os.path.normpath(out)}")


if __name__ == "__main__":
    main()
