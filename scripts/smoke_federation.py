#!/usr/bin/env python
"""Federation smoke: two in-process agents -> local aggregator -> query.

`make smoke-federation` (non-gating CI artifact, like bench-host/
bench-evict): spins up a FederationAggregatorService on ephemeral ports,
two TpuSketchExporters pushing delta frames through the REAL gRPC seam,
folds a deterministic record stream through each, flushes both windows,
and asserts the cluster-wide /federation/topk answer merges both agents'
traffic. Prints ONE JSON line with what it saw.

`--failure-path` (`make smoke-federation-chaos`, also driven by
tests/test_federation_chaos.py) runs the RAINY day instead: the agents
come up FIRST and push into nothing (cold start — their sinks walk the
retry ladder and drop), the aggregator starts late and catches up on the
next window, is then shut down and restarted once mid-run (restoring from
its checkpoint), while a query poller hammers the surface asserting it
never serves a torn snapshot (every response internally consistent, seq/
window monotonically non-decreasing across the restart thanks to the
restored window counter).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    from netobserv_tpu.utils.platform import maybe_force_cpu
    maybe_force_cpu()

    from netobserv_tpu.config import AgentConfig
    from netobserv_tpu.exporter.federation import FederationDeltaSink
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.federation.service import FederationAggregatorService
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig
    from netobserv_tpu.utils import tracing

    # sample everything: the smoke asserts ONE cross-process trace end to
    # end (agent window span + the aggregator's continued child spans under
    # the same trace id, looked up via /debug/traces?trace= on the
    # aggregator's query surface)
    tracing.configure(sample=1.0, capacity=64)

    cfg = AgentConfig()
    cfg.sketch_cm_depth, cfg.sketch_cm_width = 2, 4096
    cfg.sketch_hll_precision, cfg.sketch_topk = 8, 128
    cfg.federation_listen_port = 0   # ephemeral
    cfg.federation_query_port = 0    # ephemeral
    cfg.federation_window = 3600.0
    reports: list[dict] = []
    svc = FederationAggregatorService(cfg, sink=reports.append)
    svc.start()

    def make_records(agent: int, n: int = 256) -> list[Record]:
        now = time.time_ns()
        out = []
        for i in range(n):
            # one shared mega-flow both agents see + per-agent chatter
            if i % 4 == 0:
                key = FlowKey.make("10.9.9.9", "10.8.8.8", 5000, 443, 6)
                nbytes = 1_000_000
            else:
                key = FlowKey.make(f"10.{agent}.0.{i % 50}",
                                   f"10.{agent}.1.{i % 20}",
                                   1024 + i, 443, 6)
                nbytes = 1000 + i
            out.append(Record(
                key=key, bytes_=nbytes, packets=3, eth_protocol=0x0800,
                tcp_flags=0x12, direction=1, if_index=1, interface="eth0",
                time_flow_start_ns=now - 10**9, time_flow_end_ns=now))
        return out

    sketch_cfg = SketchConfig(cm_depth=2, cm_width=4096, hll_precision=8,
                              topk=128)
    agents = []
    for a in range(2):
        sink = FederationDeltaSink("127.0.0.1", svc.grpc_port,
                                   metrics=svc.metrics)
        exp = TpuSketchExporter(
            batch_size=256, window_s=3600.0, sketch_cfg=sketch_cfg,
            sink=lambda obj: None, delta_sink=sink,
            agent_id=f"smoke-agent-{a}")
        exp.export_batch(make_records(a))
        exp.flush()   # closes the window and pushes the delta frame
        agents.append(exp)

    svc.aggregator.flush()  # close the aggregator window, publish

    def get(path: str) -> dict:
        url = f"http://127.0.0.1:{svc.query_port}{path}"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())

    topk = get("/federation/topk?n=10")
    status = get("/federation/status")
    card = get("/federation/cardinality")
    freq = get("/federation/frequency?src=10.9.9.9&dst=10.8.8.8"
               "&src_port=5000&dst_port=443&proto=6")
    healthz = get("/healthz")
    fleet = get("/federation/fleet")

    ok = True
    notes = []

    # one end-to-end trace: every continued agent trace in the recorder
    # carries the SAME id as the agent window trace that stamped it; the
    # ?trace= lookup on the aggregator's query surface must return spans
    # from BOTH tiers (agent "window" + continued "federation_delta")
    cont = next((t for t in tracing.snapshot()
                 if t["kind"] == "federation_delta"), None)
    trace_kinds: list[str] = []
    journey: list[dict] = []
    if cont is None:
        ok, _ = False, notes.append("no continued federation_delta trace "
                                    "in the flight recorder")
    else:
        journey = get(f"/debug/traces?trace={cont['trace_id']}")["traces"]
        trace_kinds = sorted({t["kind"] for t in journey})
        if not {"window", "federation_delta"} <= set(trace_kinds):
            ok, _ = False, notes.append(
                f"trace {cont['trace_id']} did not span both tiers: "
                f"{trace_kinds}")
        stages = {s["stage"] for t in journey for st in [t["stages"]]
                  for s in st}
        if not {"delta_validate", "report_render"} & stages:
            ok, _ = False, notes.append(
                f"aggregator child spans missing from {cont['trace_id']}: "
                f"{sorted(stages)}")

    # fleet rollup: both agents' telemetry blocks present and sane
    fleet_agents = sorted(fleet.get("agents", {}))
    if fleet_agents != ["smoke-agent-0", "smoke-agent-1"]:
        ok, _ = False, notes.append(
            f"/federation/fleet missing agents: {fleet_agents}")
    for aid, row in fleet.get("agents", {}).items():
        tel = row.get("telemetry") or {}
        if tel.get("windows_published", 0) < 1 or \
                tel.get("shed_factor", 0) <= 0:
            ok, _ = False, notes.append(
                f"fleet telemetry for {aid} not populated: {tel}")
    if len(status["agents"]) != 2:
        ok, _ = False, notes.append("expected 2 agents in /status")
    hh = topk["topk"]
    if not hh or hh[0]["SrcAddr"] != "10.9.9.9":
        ok, _ = False, notes.append(
            "shared mega-flow is not the top heavy hitter")
    if card["records"] != 512.0:
        ok, _ = False, notes.append(f"records {card['records']} != 512")
    if freq["est_bytes"] < 2 * 64 * 1_000_000:  # both agents' shares
        ok, _ = False, notes.append("frequency underestimates the "
                                    "cluster-wide mega-flow")
    if healthz.get("status") != "Started":
        ok, _ = False, notes.append(f"healthz says {healthz.get('status')}")

    for exp in agents:
        exp.close()
    svc.shutdown()
    print(json.dumps({
        "metric": "smoke_federation", "ok": ok, "notes": notes,
        "agents": sorted(status["agents"]),
        "top1": hh[0] if hh else None,
        "records": card["records"],
        "distinct_src_estimate": card["distinct_src_estimate"],
        "megaflow_est_bytes": freq["est_bytes"],
        "megaflow_bound_bytes": freq["overestimate_bound_bytes"],
        "reports_published": len(reports),
        # CI artifact extras: the fleet snapshot + ONE rendered
        # cross-process trace (agent + aggregator spans, one id)
        "fleet": fleet,
        "trace_id": cont["trace_id"] if cont else None,
        "trace_kinds": trace_kinds,
        "trace": journey,
    }))
    return 0 if ok else 1


def run_failure_path(checkpoint_dir: str = "") -> dict:
    """Cold-start + mid-run-restart schedule; returns the result dict
    (also usable in-process by tests/test_federation_chaos.py). The
    caller owns `checkpoint_dir` cleanup; "" runs without checkpointing
    (the window counter then restarts at 0 — seq monotonicity is only
    asserted when a checkpoint dir is given)."""
    from netobserv_tpu.utils.platform import maybe_force_cpu
    maybe_force_cpu()

    from netobserv_tpu.config import AgentConfig
    from netobserv_tpu.exporter.federation import FederationDeltaSink
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.federation.service import FederationAggregatorService
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig

    # reserve a FIXED port so the restarted aggregator comes back where
    # the agents' sinks are already pointed (ephemeral would re-roll it)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    grpc_port = s.getsockname()[1]
    s.close()

    cfg = AgentConfig()
    cfg.sketch_cm_depth, cfg.sketch_cm_width = 2, 1024
    cfg.sketch_hll_precision, cfg.sketch_topk = 6, 32
    cfg.federation_listen_port = grpc_port
    cfg.federation_query_port = 0
    cfg.federation_window = 3600.0
    cfg.federation_checkpoint_dir = checkpoint_dir

    notes: list[str] = []
    torn: list[str] = []
    reports: list[dict] = []
    query_port = [0]          # mutable: restarts re-seat the ephemeral port
    stop_poll = threading.Event()
    seen: list[tuple[int, int]] = []   # (seq, window) per good response

    def poller() -> None:
        while not stop_poll.wait(0.02):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{query_port[0]}"
                        "/federation/topk?n=5", timeout=5) as r:
                    obj = json.loads(r.read())
            except (urllib.error.URLError, OSError, ValueError):
                continue  # down/restarting or no window yet: that is fine
            # torn = structurally inconsistent, not merely unavailable
            # (window, seq) ordering: the WINDOW counter is the durable
            # one (checkpoint-restored across restarts); seq breaks ties
            # within one process incarnation
            if not {"window", "ts_ms", "seq", "topk"} <= obj.keys():
                torn.append(f"missing keys: {sorted(obj.keys())}")
            elif seen and checkpoint_dir \
                    and (obj["window"], obj["seq"]) < seen[-1]:
                # without a checkpoint the restarted window counter
                # legitimately restarts at 0 — only a CHECKPOINTED
                # aggregator owes the poller monotonicity
                torn.append(f"snapshot went backwards: {seen[-1]} -> "
                            f"({obj['window']}, {obj['seq']})")
            else:
                seen.append((obj["window"], obj["seq"]))

    def make_records(agent: int, salt: int, n: int = 128) -> list[Record]:
        now = time.time_ns()
        out = []
        for i in range(n):
            key = FlowKey.make(f"10.{agent}.{salt}.{i % 30}",
                               f"10.{agent}.200.{i % 10}",
                               1024 + i, 443, 6)
            out.append(Record(
                key=key, bytes_=1000 + i, packets=3, eth_protocol=0x0800,
                tcp_flags=0x12, direction=1, if_index=1, interface="eth0",
                time_flow_start_ns=now - 10**9, time_flow_end_ns=now))
        return out

    sketch_cfg = SketchConfig(cm_depth=2, cm_width=1024, hll_precision=6,
                              topk=32)
    agents, sinks = [], []
    for a in range(2):
        sink = FederationDeltaSink("127.0.0.1", grpc_port, retries=2,
                                   backoff_initial_s=0.05, timeout_s=5.0)
        exp = TpuSketchExporter(
            batch_size=128, window_s=3600.0, sketch_cfg=sketch_cfg,
            sink=lambda obj: None, delta_sink=sink,
            agent_id=f"chaos-agent-{a}")
        agents.append(exp)
        sinks.append(sink)

    def push_window(salt: int) -> None:
        for a, exp in enumerate(agents):
            exp.export_batch(make_records(a, salt))
            exp.flush()

    # window 0: NOTHING is listening — cold start; ladders exhaust, frames
    # drop (per-window snapshots: the next window supersedes them)
    push_window(salt=0)

    svc = FederationAggregatorService(cfg, sink=reports.append)
    svc.start()
    query_port[0] = svc.query_port
    threading.Thread(target=poller, daemon=True).start()

    # window 1: catch-up — the late aggregator now sees both agents
    push_window(salt=1)
    svc.aggregator.flush()
    status1 = svc.aggregator.status()

    # mid-run restart (graceful here; the SIGKILL flavor is pinned by
    # tests/test_federation_chaos.py against the checkpoint semantics)
    svc.shutdown()
    svc2 = FederationAggregatorService(cfg, sink=reports.append)
    svc2.start()
    query_port[0] = svc2.query_port

    # window 2: the restarted aggregator serves on, sinks reconnect
    push_window(salt=2)
    svc2.aggregator.flush()
    status2 = svc2.aggregator.status()
    time.sleep(0.2)          # a few poller rounds against the new snapshot
    stop_poll.set()

    ok = True
    if len(status1["agents"]) != 2 or len(status2["agents"]) != 2:
        ok, _ = False, notes.append("expected 2 agents registered in both "
                                    "aggregator incarnations")
    if torn:
        ok, _ = False, notes.append(f"torn snapshots: {torn[:3]}")
    if not seen:
        ok, _ = False, notes.append("poller never saw a published window")
    # published reports: window 1 (pre-restart) + windows from svc2; the
    # cold-start window 0 must be absent everywhere (it was dropped)
    if len(reports) < 2:
        ok, _ = False, notes.append(
            f"expected >=2 published windows, saw {len(reports)}")
    per_window = 2 * 128.0
    recs = [r["Records"] for r in reports]
    if any(r > per_window for r in recs):
        ok, _ = False, notes.append(
            f"a window over-counted: {recs} (> {per_window}/window means "
            "a dropped/cold-start frame leaked back in)")
    if checkpoint_dir and status2.get("last_published_window") is not None \
            and status1.get("last_published_window") is not None \
            and status2["last_published_window"] \
            <= status1["last_published_window"]:
        ok, _ = False, notes.append(
            "restored window counter did not advance past the "
            "pre-restart one")

    for exp in agents:
        exp.close()
    svc2.shutdown()
    return {
        "metric": "smoke_federation_chaos", "ok": ok, "notes": notes,
        "agents": sorted(status2["agents"]),
        "published_windows": recs,
        "poll_responses": len(seen),
        "torn_responses": len(torn),
        "last_published_window": status2.get("last_published_window"),
        "checkpointed": bool(checkpoint_dir),
    }


def main_failure_path() -> int:
    import tempfile
    with tempfile.TemporaryDirectory(prefix="fed-ckpt-") as d:
        out = run_failure_path(checkpoint_dir=d)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main_failure_path() if "--failure-path" in sys.argv
             else main())
