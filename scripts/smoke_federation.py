#!/usr/bin/env python
"""Federation smoke: two in-process agents -> local aggregator -> query.

`make smoke-federation` (non-gating CI artifact, like bench-host/
bench-evict): spins up a FederationAggregatorService on ephemeral ports,
two TpuSketchExporters pushing delta frames through the REAL gRPC seam,
folds a deterministic record stream through each, flushes both windows,
and asserts the cluster-wide /federation/topk answer merges both agents'
traffic. Prints ONE JSON line with what it saw.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    from netobserv_tpu.utils.platform import maybe_force_cpu
    maybe_force_cpu()

    from netobserv_tpu.config import AgentConfig
    from netobserv_tpu.exporter.federation import FederationDeltaSink
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.federation.service import FederationAggregatorService
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig

    cfg = AgentConfig()
    cfg.sketch_cm_depth, cfg.sketch_cm_width = 2, 4096
    cfg.sketch_hll_precision, cfg.sketch_topk = 8, 128
    cfg.federation_listen_port = 0   # ephemeral
    cfg.federation_query_port = 0    # ephemeral
    cfg.federation_window = 3600.0
    reports: list[dict] = []
    svc = FederationAggregatorService(cfg, sink=reports.append)
    svc.start()

    def make_records(agent: int, n: int = 256) -> list[Record]:
        now = time.time_ns()
        out = []
        for i in range(n):
            # one shared mega-flow both agents see + per-agent chatter
            if i % 4 == 0:
                key = FlowKey.make("10.9.9.9", "10.8.8.8", 5000, 443, 6)
                nbytes = 1_000_000
            else:
                key = FlowKey.make(f"10.{agent}.0.{i % 50}",
                                   f"10.{agent}.1.{i % 20}",
                                   1024 + i, 443, 6)
                nbytes = 1000 + i
            out.append(Record(
                key=key, bytes_=nbytes, packets=3, eth_protocol=0x0800,
                tcp_flags=0x12, direction=1, if_index=1, interface="eth0",
                time_flow_start_ns=now - 10**9, time_flow_end_ns=now))
        return out

    sketch_cfg = SketchConfig(cm_depth=2, cm_width=4096, hll_precision=8,
                              topk=128)
    agents = []
    for a in range(2):
        sink = FederationDeltaSink("127.0.0.1", svc.grpc_port,
                                   metrics=svc.metrics)
        exp = TpuSketchExporter(
            batch_size=256, window_s=3600.0, sketch_cfg=sketch_cfg,
            sink=lambda obj: None, delta_sink=sink,
            agent_id=f"smoke-agent-{a}")
        exp.export_batch(make_records(a))
        exp.flush()   # closes the window and pushes the delta frame
        agents.append(exp)

    svc.aggregator.flush()  # close the aggregator window, publish

    def get(path: str) -> dict:
        url = f"http://127.0.0.1:{svc.query_port}{path}"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())

    topk = get("/federation/topk?n=10")
    status = get("/federation/status")
    card = get("/federation/cardinality")
    freq = get("/federation/frequency?src=10.9.9.9&dst=10.8.8.8"
               "&src_port=5000&dst_port=443&proto=6")
    healthz = get("/healthz")

    ok = True
    notes = []
    if len(status["agents"]) != 2:
        ok, _ = False, notes.append("expected 2 agents in /status")
    hh = topk["topk"]
    if not hh or hh[0]["SrcAddr"] != "10.9.9.9":
        ok, _ = False, notes.append(
            "shared mega-flow is not the top heavy hitter")
    if card["records"] != 512.0:
        ok, _ = False, notes.append(f"records {card['records']} != 512")
    if freq["est_bytes"] < 2 * 64 * 1_000_000:  # both agents' shares
        ok, _ = False, notes.append("frequency underestimates the "
                                    "cluster-wide mega-flow")
    if healthz.get("status") != "Started":
        ok, _ = False, notes.append(f"healthz says {healthz.get('status')}")

    for exp in agents:
        exp.close()
    svc.shutdown()
    print(json.dumps({
        "metric": "smoke_federation", "ok": ok, "notes": notes,
        "agents": sorted(status["agents"]),
        "top1": hh[0] if hh else None,
        "records": card["records"],
        "distinct_src_estimate": card["distinct_src_estimate"],
        "megaflow_est_bytes": freq["est_bytes"],
        "megaflow_bound_bytes": freq["overestimate_bound_bytes"],
        "reports_published": len(reports),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
