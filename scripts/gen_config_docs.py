#!/usr/bin/env python3
"""Regenerate docs/config.md from the AgentConfig dataclass (make gen-docs)."""
import dataclasses
import sys

sys.path.insert(0, ".")
from netobserv_tpu.config import AgentConfig, _DURATION_FIELDS  # noqa: E402

out = []
out.append("# Configuration\n")
out.append("All configuration is environment-driven (no flags, no files), matching")
out.append("the reference agent's surface. Durations use Go syntax (`5s`, `300ms`, `1m30s`).\n")
out.append("| Env var | Default | Type | Field |")
out.append("|---|---|---|---|")
for f in dataclasses.fields(AgentConfig):
    env = f.metadata.get("env", "")
    if not env:
        continue
    default = f.metadata.get("default", "")
    typ = ("duration" if f.name in _DURATION_FIELDS
           else (f.type if isinstance(f.type, str) else f.type.__name__))
    out.append(f"| `{env}` | `{default}` | {typ} | {f.name} |")
out.append("")
out.append("## Notes")
out.append("- `EXPORT` selects the backend: `grpc`, `kafka`, `ipfix+udp`, `ipfix+tcp`,")
out.append("  `direct-flp`, `stdout`, or the TPU-native `tpu-sketch`.")
out.append("- `FLOW_FILTER_RULES` takes a JSON array of rule objects (see docs/flow_filtering.md).")
out.append("- `SKETCH_*` knobs configure the tpu-sketch backend (sizes must be powers of two where noted).")
out.append("- `DATAPATH` (this framework only): `kernel`, `synthetic`, `pcap:<path>`, or `grpc:<port>`.")
out.append("- `UDN_MAPPING_FILE` (this framework only): JSON {iface: udn} map for ENABLE_UDN_MAPPING.")
with open("docs/config.md", "w") as fh:
    fh.write("\n".join(out) + "\n")
print("docs/config.md regenerated")
