#!/usr/bin/env python
"""Alerting-plane smoke (`make smoke`): one live raise→clear cycle
against the REAL binary.

Builds the zoo's syn_flood pcap, launches `python -m netobserv_tpu` with
the tpu-sketch exporter + the continuous detection plane enabled
(ALERT_RULES=default, mid-window refresh on, short windows), and polls
the live `/query/alerts` HTTP route until

1. the `syn_flood` alert RAISEs (with the victim named), then
2. the flood rolls out of the window and the alert CLEARs
   (a `clear` transition lands in the ring and the active set empties),

then SIGTERMs the agent and expects a clean exit. Everything end to end
is the production path: pcap replay datapath -> columnar fold -> window
roll -> snapshot publish -> alert engine -> metrics-server HTTP.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RAISE_DEADLINE_S = 240.0   # includes the first on-CPU sketch compile
CLEAR_DEADLINE_S = 90.0


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def get(port: int, path: str):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, json.loads(r.read())
    except (urllib.error.URLError, ConnectionError, OSError):
        return 0, {}


def main() -> int:
    from netobserv_tpu.scenarios.zoo import build_syn_flood

    workdir = tempfile.mkdtemp(prefix="smoke_alerts_")
    pcap = os.path.join(workdir, "syn_flood.pcap")
    truth = build_syn_flood(pcap)
    port = free_port()
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               DATAPATH=f"pcap:{pcap}",
               EXPORT="tpu-sketch",
               CACHE_ACTIVE_TIMEOUT="300ms",
               METRICS_ENABLE="true",
               METRICS_SERVER_ADDRESS="127.0.0.1",
               METRICS_SERVER_PORT=str(port),
               ALERT_RULES="default",
               ALERT_RAISE_EVALS="1",
               ALERT_CLEAR_EVALS="2",
               # short windows: the flood's window closes and the empty
               # follow-up windows drive the quiet evals that CLEAR
               SKETCH_WINDOW="3s",
               SKETCH_QUERY_REFRESH="500ms",
               SKETCH_BATCH_SIZE="512",
               SKETCH_CM_WIDTH="16384",
               SKETCH_TOPK="256",
               SKETCH_HLL_PRECISION="12",
               SKETCH_SUPERBATCH="1",
               SKETCH_SYNFLOOD_MIN="64",
               SKETCH_SYNFLOOD_RATIO="8",
               LOG_LEVEL="info")
    # stderr to a FILE, never an undrained pipe: a chatty or error-looping
    # agent would fill a ~64KB pipe and block its logging thread — the
    # smoke would then report "never raised" while the actual error sat
    # stuck in the pipe
    errlog = os.path.join(workdir, "agent.stderr")
    errfh = open(errlog, "wb")
    try:
        proc = subprocess.Popen([sys.executable, "-m", "netobserv_tpu"],
                                env=env, stdout=subprocess.DEVNULL,
                                stderr=errfh)
    except BaseException:
        errfh.close()
        raise
    raised = cleared = False
    victim_named = False
    try:
        deadline = time.monotonic() + RAISE_DEADLINE_S
        # keep polling until the victim is NAMED (or the deadline): the
        # naming is OR-accumulated across buckets and views — a second
        # victim-less syn_flood bucket, or an early view whose bucket
        # detail has not named the victim yet, must not latch False
        while time.monotonic() < deadline and not (raised and
                                                   victim_named):
            if proc.poll() is not None:
                break
            code, view = get(port, "/query/alerts")
            if code == 200:
                for a in view.get("active", ()):
                    if a["rule"] == "syn_flood":
                        if not raised:
                            print(f"RAISED: syn_flood "
                                  f"bucket={a['bucket']} "
                                  f"victims={a['victims']}")
                        raised = True
                        victim_named = victim_named or (
                            truth["victim"] in a.get("victims", ()))
            time.sleep(0.25)
        if raised:
            deadline = time.monotonic() + CLEAR_DEADLINE_S
            while time.monotonic() < deadline and not cleared:
                if proc.poll() is not None:
                    break
                code, view = get(port, "/query/alerts")
                if code == 200:
                    active = {a["rule"] for a in view.get("active", ())}
                    clears = [t for t in view.get("recent", ())
                              if t["rule"] == "syn_flood"
                              and t["action"] == "clear"]
                    if "syn_flood" not in active and clears:
                        cleared = True
                        print(f"CLEARED: transition seq "
                              f"{clears[-1]['seq']}")
                time.sleep(0.25)
    finally:
        try:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                print("FAIL: agent did not exit cleanly on SIGTERM")
                sys.stderr.write(tail_errlog(errlog))
                return 1
        finally:
            errfh.close()
    if not raised:
        print("FAIL: syn_flood alert never raised on /query/alerts")
    elif not victim_named:
        print(f"FAIL: victim {truth['victim']} not named by the alert")
    elif not cleared:
        print("FAIL: alert never cleared after the flood window closed")
    elif proc.returncode != 0:
        print(f"FAIL: agent exited rc={proc.returncode}")
    else:
        print("PASS: live raise→clear cycle through the real binary")
        return 0
    sys.stderr.write(tail_errlog(errlog))
    return 1


def tail_errlog(path: str, n: int = 4000) -> str:
    try:
        with open(path, "rb") as fh:
            return fh.read().decode(errors="replace")[-n:]
    except OSError:
        return ""


if __name__ == "__main__":
    sys.exit(main())
